"""REST client for the API server — the rest.Request analogue
(client-go rest/request.go reduced to the verbs our server speaks).
Returns api.types objects via the wire codec; raises the store's own
exception types on the mapped status codes."""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, List, Optional, Tuple

from ..api import store as st
from ..api import wire


def _ns_seg(namespace: str) -> str:
    """URL segment for a namespace; cluster-scoped objects (Node) use
    namespace "" which would collapse out of the path — '-' is the
    reserved sentinel the server maps back."""
    return namespace if namespace else "-"


class RestClient:
    def __init__(
        self,
        base_url: str,
        timeout: float = 10.0,
        token: Optional[str] = None,
    ):
        self.base = base_url.rstrip("/")
        self.timeout = timeout
        self.token = token

    def _headers(self) -> dict:
        h = {"Content-Type": "application/json"}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        return h

    @staticmethod
    def _map_http_error(e: urllib.error.HTTPError):
        try:
            doc = json.load(e)
        except Exception:
            doc = {"error": str(e), "reason": ""}
        exc = {
            "NotFound": st.NotFound,
            "AlreadyExists": st.AlreadyExists,
            "Conflict": st.Conflict,
            "Expired": st.Expired,
        }.get(doc.get("reason"), RuntimeError)
        if exc is RuntimeError and e.code == 410:
            exc = st.Expired
        raise exc(doc.get("error", str(e))) from None

    def _call(self, method: str, path: str, body: Any = None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.base + path, data=data, method=method,
            headers=self._headers(),
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return json.load(r)
        except urllib.error.HTTPError as e:
            self._map_http_error(e)

    # -- typed verbs -------------------------------------------------------

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[str] = None,
        field_selector: Optional[str] = None,
    ) -> Tuple[List[Any], int]:
        from urllib.parse import urlencode

        params = {}
        if namespace is not None:
            params["namespace"] = namespace
        if label_selector:
            params["labelSelector"] = label_selector
        if field_selector:
            params["fieldSelector"] = field_selector
        path = f"/api/v1/{kind}"
        if params:
            path += "?" + urlencode(params)
        doc = self._call("GET", path)
        return [wire.from_wire(d) for d in doc["items"]], doc["resourceVersion"]

    def get(self, kind: str, name: str, namespace: str = "default"):
        return wire.from_wire(
            self._call("GET", f"/api/v1/{kind}/{_ns_seg(namespace)}/{name}")
        )

    def create(self, obj: Any):
        kind = obj.KIND
        return wire.from_wire(
            self._call("POST", f"/api/v1/{kind}", wire.to_wire(obj))
        )

    def update(self, obj: Any, force: bool = False):
        kind = obj.KIND
        path = f"/api/v1/{kind}/{_ns_seg(obj.meta.namespace)}/{obj.meta.name}"
        if force:
            path += "?force=1"
        return wire.from_wire(self._call("PUT", path, wire.to_wire(obj)))

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        self._call("DELETE", f"/api/v1/{kind}/{_ns_seg(namespace)}/{name}")

    def patch(
        self,
        kind: str,
        name: str,
        patch: Any,
        namespace: str = "default",
        subresource: Optional[str] = None,
    ):
        """RFC 7386 merge patch; subresource="status" patches only
        .status (the PATCH pods/{name}/status controllers use)."""
        path = f"/api/v1/{kind}/{_ns_seg(namespace)}/{name}"
        if subresource:
            path += f"/{subresource}"
        return wire.from_wire(self._call("PATCH", path, patch))

    def update_status(self, obj: Any):
        """PUT the status subresource: only .status from obj lands."""
        kind = obj.KIND
        path = (
            f"/api/v1/{kind}/{_ns_seg(obj.meta.namespace)}"
            f"/{obj.meta.name}/status"
        )
        return wire.from_wire(self._call("PUT", path, wire.to_wire(obj)))

    def watch(self, kind: str, from_rv: Optional[int] = None):
        """Generator of (type, obj, rv) from the chunked watch stream.

        Error contract: a stale from_rv raises st.Expired up front (the
        410 relist signal), and a stream the SERVER ends (overflowed
        watcher terminated, server restart) raises st.Expired at the end
        — a silent return would freeze a remote reflector on stale state;
        relist-and-rewatch is always the correct reaction.  The read
        timeout is safe because the server emits 1s BOOKMARK keepalives."""
        path = f"/api/v1/watch/{kind}"
        if from_rv is not None:
            path += f"?from_rv={from_rv}"
        req = urllib.request.Request(self.base + path, headers=self._headers())
        try:
            stream = urllib.request.urlopen(
                req, timeout=max(self.timeout, 5.0)
            )
        except urllib.error.HTTPError as e:
            self._map_http_error(e)
        with stream as r:
            for line in r:
                line = line.strip()
                if not line:
                    continue
                doc = json.loads(line)
                if doc["type"] == "BOOKMARK":
                    continue  # idle keepalive frames (watch bookmarks)
                yield doc["type"], wire.from_wire(doc["object"]), doc["rv"]
        raise st.Expired(f"watch stream for {kind} ended; relist and rewatch")
