"""EventRecorder: the record/events broadcaster reduced to store writes
with client-go-style aggregation.

Reference: client-go tools/record (EventBroadcaster/EventRecorder) and
the scheduler's call sites (fwk.EventRecorder().Eventf,
schedule_one.go:1003,1094).  Repeats of the same (object, reason,
message) bump `count` on one Event object instead of flooding the store
— the events correlator's aggregation behaviour.

Two modes:
  sync (default)  — eventf writes through immediately (tests, CLI).
  async           — eventf enqueues and a broadcaster thread drains on
                    a short interval, coalescing repeats in-queue
                    before they ever hit the store.  This is the
                    reference's actual shape (the broadcaster's
                    buffered channel; record.go NewBroadcaster): a bind
                    wave of 4k pods must not pay 4k synchronous store
                    writes on the scheduling thread.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..api import store as st
from ..api import types as api

_QUEUE_CAP = 8192  # broadcaster channel capacity; overflow drops (record.go)


class EventRecorder:
    def __init__(
        self,
        store: st.Store,
        component: str = "default-scheduler",
        ttl: float = 3600.0,
        clock=time.time,
        async_mode: bool = False,
        flush_interval: float = 0.05,
    ):
        self.store = store
        self.component = component
        # the reference apiserver bounds Events with a TTL (default 1h,
        # --event-ttl); without expiry a long-running scheduler grows the
        # store (and journal compactions) without bound
        self.ttl = ttl
        self._clock = clock
        self._writes = 0
        self._async = async_mode
        self._flush_interval = flush_interval
        self._queue: List[Tuple[Any, str, str, str, float]] = []
        self._qlock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if async_mode:
            self._thread = threading.Thread(
                target=self._broadcaster, name="event-broadcaster", daemon=True
            )
            self._thread.start()

    def eventf(
        self, obj: Any, event_type: str, reason: str, message: str
    ) -> None:
        """Record one event for obj; never raises into the caller (events
        are best-effort observability, not control flow)."""
        if self._async:
            with self._qlock:
                if len(self._queue) < _QUEUE_CAP:
                    self._queue.append(
                        (obj, event_type, reason, message, self._clock())
                    )
            return
        try:
            self._record(obj, event_type, reason, message, self._clock())
        except Exception:
            pass

    # -- async broadcaster --------------------------------------------------

    def _broadcaster(self) -> None:
        while not self._stop.wait(self._flush_interval):
            self.flush()
        self.flush()

    def flush(self) -> None:
        """Drain the queue, coalescing repeats of (object, reason,
        message) into one store write with the summed count."""
        with self._qlock:
            batch, self._queue = self._queue, []
        if not batch:
            return
        merged: Dict[Tuple[str, str, str, str], list] = {}
        for obj, event_type, reason, message, ts in batch:
            # event_type is part of the identity (matching _record's
            # same-type check): a Normal and a Warning repeat of the same
            # reason/message must not merge into one record whose type is
            # whichever arrived first
            key = (
                obj.meta.namespace,
                f"{obj.meta.name}.{reason.lower()}",
                event_type,
                message,
            )
            slot = merged.get(key)
            if slot is None:
                merged[key] = [obj, event_type, reason, message, ts, 1]
            else:
                slot[4] = ts
                slot[5] += 1
        for obj, event_type, reason, message, ts, n in merged.values():
            try:
                self._record(obj, event_type, reason, message, ts, count=n)
            except Exception:
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
        self.flush()

    # -- write-through ------------------------------------------------------

    def _record(
        self,
        obj: Any,
        event_type: str,
        reason: str,
        message: str,
        now: float,
        count: int = 1,
    ) -> None:
        meta = obj.meta
        name = f"{meta.name}.{reason.lower()}"
        self._writes += 1
        if self._writes % 256 == 0:
            self._expire(now)
        try:
            ev = self.store.get("Event", name, meta.namespace)
            if ev.message == message and ev.type == event_type:
                ev.count += count
                ev.last_timestamp = now
                self.store.update(ev, force=True, copy_result=False)
                return
            self.store.delete("Event", name, meta.namespace)
        except KeyError:
            pass
        self.store.create(
            api.Event(
                meta=api.ObjectMeta(name=name, namespace=meta.namespace),
                involved_object=api.ObjectReference(
                    kind=getattr(obj, "KIND", ""),
                    name=meta.name,
                    namespace=meta.namespace,
                    uid=meta.uid,
                ),
                reason=reason,
                message=message,
                type=event_type,
                first_timestamp=now,
                last_timestamp=now,
                source_component=self.component,
                count=count,
            )
        )

    def _expire(self, now: float) -> None:
        """Drop events past the TTL (the --event-ttl sweep)."""
        events, _ = self.store.list("Event")
        for ev in events:
            if now - ev.last_timestamp > self.ttl:
                try:
                    self.store.delete("Event", ev.meta.name, ev.meta.namespace)
                except KeyError:
                    pass
