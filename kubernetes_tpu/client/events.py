"""EventRecorder: the record/events broadcaster reduced to direct store
writes with client-go-style aggregation.

Reference: client-go tools/record (EventBroadcaster/EventRecorder) and
the scheduler's call sites (fwk.EventRecorder().Eventf,
schedule_one.go:1003,1094).  Repeats of the same (object, reason,
message) bump `count` on one Event object instead of flooding the store
— the events correlator's aggregation behaviour.
"""

from __future__ import annotations

import time
from typing import Any

from ..api import store as st
from ..api import types as api


class EventRecorder:
    def __init__(
        self,
        store: st.Store,
        component: str = "default-scheduler",
        ttl: float = 3600.0,
        clock=time.time,
    ):
        self.store = store
        self.component = component
        # the reference apiserver bounds Events with a TTL (default 1h,
        # --event-ttl); without expiry a long-running scheduler grows the
        # store (and journal compactions) without bound
        self.ttl = ttl
        self._clock = clock
        self._writes = 0

    def eventf(
        self, obj: Any, event_type: str, reason: str, message: str
    ) -> None:
        """Record one event for obj; never raises into the caller (events
        are best-effort observability, not control flow)."""
        try:
            self._record(obj, event_type, reason, message)
        except Exception:
            pass

    def _record(self, obj: Any, event_type: str, reason: str, message: str) -> None:
        meta = obj.meta
        name = f"{meta.name}.{reason.lower()}"
        now = self._clock()
        self._writes += 1
        if self._writes % 256 == 0:
            self._expire(now)
        try:
            ev = self.store.get("Event", name, meta.namespace)
            if ev.message == message and ev.type == event_type:
                ev.count += 1
                ev.last_timestamp = now
                self.store.update(ev, force=True)
                return
            self.store.delete("Event", name, meta.namespace)
        except KeyError:
            pass
        self.store.create(
            api.Event(
                meta=api.ObjectMeta(name=name, namespace=meta.namespace),
                involved_object=api.ObjectReference(
                    kind=getattr(obj, "KIND", ""),
                    name=meta.name,
                    namespace=meta.namespace,
                    uid=meta.uid,
                ),
                reason=reason,
                message=message,
                type=event_type,
                first_timestamp=now,
                last_timestamp=now,
                source_component=self.component,
            )
        )

    def _expire(self, now: float) -> None:
        """Drop events past the TTL (the --event-ttl sweep)."""
        events, _ = self.store.list("Event")
        for ev in events:
            if now - ev.last_timestamp > self.ttl:
                try:
                    self.store.delete("Event", ev.meta.name, ev.meta.namespace)
                except KeyError:
                    pass
