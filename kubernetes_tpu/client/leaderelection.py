"""Lease-based leader election.

Reference: client-go tools/leaderelection/leaderelection.go:181-245 —
tryAcquireOrRenew under optimistic concurrency against a Lease object;
the holder renews every RetryPeriod, standbys watch the renew time and
take over when LeaseDuration elapses without one.  Fail-over therefore
bounds at lease_duration + one retry period, and split-brain is
excluded by the store's Conflict-on-stale-rv semantics (the etcd
transaction's analogue).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from ..api import store as st
from ..api import types as api
from ..testing import faults


class LeaderElector:
    def __init__(
        self,
        store: st.Store,
        lease_name: str,
        identity: str,
        namespace: str = "kube-system",
        lease_duration: float = 15.0,
        renew_period: float = 2.0,
        clock=time.monotonic,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ):
        self.store = store
        self.lease_name = lease_name
        self.identity = identity
        self.namespace = namespace
        self.lease_duration = lease_duration
        self.renew_period = renew_period
        self._clock = clock
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._leading = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # renew attempts that raised (store fault, injected failure) and
        # were treated as a failed renew rather than killing the loop
        self.renew_errors = 0
        # lease_transitions observed when THIS identity last acquired:
        # the write-fencing generation (Store.update_wave fence=...).
        # Written only by the elector thread; read cross-thread as one
        # atomic int (a stale read just means a fenced commit, which is
        # the safe direction).  -1 = never acquired.
        self._generation = -1

    # -- the tryAcquireOrRenew step ----------------------------------------

    def try_acquire_or_renew(self) -> bool:
        faults.fire("leader.renew", identity=self.identity)
        now = self._clock()
        try:
            lease = self.store.get("Lease", self.lease_name, self.namespace)
        except st.NotFound:
            lease = api.Lease(
                meta=api.ObjectMeta(
                    name=self.lease_name, namespace=self.namespace
                ),
                spec=api.LeaseSpec(
                    holder_identity=self.identity,
                    lease_duration_seconds=int(self.lease_duration),
                    acquire_time=now,
                    renew_time=now,
                ),
            )
            try:
                self.store.create(lease)
                self._generation = 0  # first acquisition of a new lease
                return True
            except st.AlreadyExists:
                return False  # raced; retry next period
        spec = lease.spec
        if (
            spec.holder_identity != self.identity
            and now < spec.renew_time + self.lease_duration
        ):
            return False  # someone else holds a live lease
        took_over = spec.holder_identity != self.identity
        spec.holder_identity = self.identity
        spec.renew_time = now
        if took_over:
            spec.acquire_time = now
            spec.lease_transitions += 1
        try:
            self.store.update(lease)
            self._generation = spec.lease_transitions
            return True
        except (st.Conflict, st.NotFound):
            return False  # raced with another candidate; retry

    def fence_token(self) -> Optional[st.FenceToken]:
        """The write-fencing proof for Store.update_wave: this
        identity's lease coordinates at its LAST acquisition.  Returned
        even after leadership is lost — a deposed leader's late wave
        must carry its stale token so the store can reject it (no token
        would mean no fencing at all).  None only before the first
        acquisition."""
        if self._generation < 0:
            return None
        return st.FenceToken(
            name=self.lease_name,
            namespace=self.namespace,
            identity=self.identity,
            generation=self._generation,
        )

    # -- run loop ----------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                got = self.try_acquire_or_renew()
            except Exception:  # noqa: BLE001 — renew containment
                # an exception mid-renew (store fault, injected failure)
                # is a FAILED renew, not a dead elector: the holder must
                # step down exactly once (below) and keep retrying — a
                # dead loop with _leading still set would be split-brain
                got = False
                self.renew_errors += 1
                logging.getLogger(__name__).exception(
                    "leader renew failed for %s; treating as lost lease",
                    self.identity,
                )
            if got and not self._leading.is_set():
                self._leading.set()
                if self.on_started_leading:
                    self.on_started_leading()
            elif not got and self._leading.is_set():
                # failed to renew: step down (the reference cancels the
                # leading context)
                self._leading.clear()
                if self.on_stopped_leading:
                    self.on_stopped_leading()
            self._stop.wait(self.renew_period)
        if self._leading.is_set():
            self._leading.clear()
            if self.on_stopped_leading:
                self.on_stopped_leading()

    def start(self) -> "LeaderElector":
        self._thread = threading.Thread(
            target=self._run, name=f"leaderelection-{self.identity}", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, release: bool = True) -> None:
        """Stop; with release (the reference's ReleaseOnCancel), zero the
        renew time so standbys take over immediately."""
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        if release:
            try:
                lease = self.store.get("Lease", self.lease_name, self.namespace)
                if lease.spec.holder_identity == self.identity:
                    lease.spec.renew_time = 0.0
                    self.store.update(lease, force=True)
            except st.NotFound:
                pass

    def is_leader(self) -> bool:
        return self._leading.is_set()

    def wait_for_leadership(self, timeout: float = 30.0) -> bool:
        return self._leading.wait(timeout)
