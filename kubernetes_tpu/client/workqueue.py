"""Rate-limited work queues — client-go util/workqueue reduced to the
semantics every controller depends on:

  * dedup: an item added while queued is processed once (queue.go's
    dirty/processing sets);
  * re-add during processing: processed again after done() (no lost
    updates);
  * per-item exponential backoff via add_rate_limited / forget
    (rate_limiting_queue.go + default_rate_limiters.go's
    ItemExponentialFailureRateLimiter);
  * add_after: delayed enqueue (delaying_queue.go).
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple


class WorkQueue:
    def __init__(
        self,
        base_delay: float = 0.005,
        max_delay: float = 1000.0,
        clock=time.monotonic,
    ):
        self._clock = clock
        self._base = base_delay
        self._max = max_delay
        self._cond = threading.Condition()
        self._queue: List[Any] = []
        self._dirty: Set[Any] = set()
        self._processing: Set[Any] = set()
        self._failures: Dict[Any, int] = {}
        self._delayed: List[Tuple[float, int, Any]] = []  # (when, seq, item)
        self._seq = 0
        self._shutdown = False

    # -- core (queue.go) ---------------------------------------------------

    def add(self, item: Any) -> None:
        with self._cond:
            if self._shutdown or item in self._dirty:
                return
            self._dirty.add(item)
            if item in self._processing:
                return
            self._queue.append(item)
            self._cond.notify()

    def get(self, timeout: Optional[float] = None) -> Optional[Any]:
        """Blocks for the next item (None on timeout/shutdown).  The item
        is 'processing' until done(item)."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while True:
                self._pump_delayed_locked()
                if self._queue:
                    item = self._queue.pop(0)
                    self._processing.add(item)
                    self._dirty.discard(item)
                    return item
                if self._shutdown:
                    return None
                wait = self._next_wait_locked(deadline)
                if wait is not None and wait <= 0:
                    return None
                self._cond.wait(wait)

    def done(self, item: Any) -> None:
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._cond.notify()

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)

    # -- delays / rate limiting -------------------------------------------

    def add_after(self, item: Any, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._cond:
            if self._shutdown:
                return
            self._seq += 1
            heapq.heappush(self._delayed, (self._clock() + delay, self._seq, item))
            self._cond.notify()

    def add_rate_limited(self, item: Any) -> None:
        """Enqueue after the item's exponential backoff (failures so far)."""
        with self._cond:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
        self.add_after(item, min(self._base * (2 ** n), self._max))

    def forget(self, item: Any) -> None:
        """Reset the item's backoff (call on successful sync)."""
        with self._cond:
            self._failures.pop(item, None)

    def num_requeues(self, item: Any) -> int:
        with self._cond:
            return self._failures.get(item, 0)

    # -- internals ---------------------------------------------------------

    def _pump_delayed_locked(self) -> None:
        now = self._clock()
        while self._delayed and self._delayed[0][0] <= now:
            _, _, item = heapq.heappop(self._delayed)
            if item in self._dirty or self._shutdown:
                continue
            self._dirty.add(item)
            if item not in self._processing:
                self._queue.append(item)

    def _next_wait_locked(self, deadline: Optional[float]) -> Optional[float]:
        """Seconds to sleep, None for forever, <=0 for 'give up now'."""
        candidates = []
        if self._delayed:
            candidates.append(self._delayed[0][0])
        if deadline is not None:
            candidates.append(deadline)
        if not candidates:
            return None
        wait = min(candidates) - self._clock()
        if deadline is not None and min(candidates) == deadline:
            return wait if wait > 0 else 0
        return max(wait, 0.001)
