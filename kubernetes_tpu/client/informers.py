"""Informer machinery: reflector + shared informer + listers.

The client-go cache stack (tools/cache) reduced to its load-bearing
parts:

  Reflector      list+watch against the Store, relisting on Expired /
                 stream termination (reflector.go:340 ListAndWatch, the
                 410-Gone relist path)
  SharedInformer local thread-safe object cache + handler fan-out
                 (shared_informer.go:459 Run; handlers get add/update/
                 delete callbacks after an initial synthetic-ADDED sync,
                 DeltaFIFO's replace semantics)
  Lister         snapshot reads of the informer cache (listers)

Transport is the in-process api.store.Store — the deployment analogue of
client-go speaking to the apiserver's watch cache.  Delivery runs on one
informer thread per kind (client-go's single event goroutine per
informer); handlers must not block it.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, List, Optional

from ..api import store as st

logger = logging.getLogger(__name__)

Handler = Callable[[str, Any, Optional[Any]], None]
# Handler(event_type, obj, old_obj): old_obj set for MODIFIED only.


class SharedInformer:
    """One kind's local cache, kept in sync by a reflector thread."""

    def __init__(self, store: st.Store, kind: str):
        self._store = store
        self.kind = kind
        self._lock = threading.RLock()
        self._cache: Dict[str, Any] = {}
        self._handlers: List[Handler] = []
        self._synced = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._watch: Optional[st.Watch] = None

    # -- wiring ------------------------------------------------------------

    def add_handler(self, handler: Handler, replay: bool = True) -> None:
        """Register a handler; when replay (the shared-informer contract),
        it first receives synthetic ADDED events for the current cache."""
        with self._lock:
            if replay:
                for obj in self._cache.values():
                    try:
                        handler(st.ADDED, obj, None)
                    except Exception:
                        logger.exception(
                            "informer %s: handler %r failed on replay",
                            self.kind, handler,
                        )
            self._handlers.append(handler)

    def start(self) -> None:
        if self._thread:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"informer-{self.kind}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        w = self._watch
        if w:
            w.stop()
        if self._thread:
            self._thread.join(timeout=5)

    def wait_for_sync(self, timeout: Optional[float] = 10) -> bool:
        """WaitForCacheSync: true once the initial list landed."""
        return self._synced.wait(timeout)

    # -- reads (listers) ---------------------------------------------------

    def get(self, name: str, namespace: str = "default") -> Optional[Any]:
        with self._lock:
            return self._cache.get(self._key(namespace, name))

    def list(self) -> List[Any]:
        with self._lock:
            return list(self._cache.values())

    @staticmethod
    def _key(namespace: str, name: str) -> str:
        return f"{namespace}/{name}" if namespace else name

    def _obj_key(self, obj: Any) -> str:
        return self._key(obj.meta.namespace, obj.meta.name)

    # -- reflector loop ----------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                rv = self._relist()
                self._synced.set()
                self._stream(rv)
            except st.Expired:
                continue  # relist (the 410 path)
            except Exception:
                if self._stop.is_set():
                    return
                self._stop.wait(0.05)  # backoff then relist

    def _relist(self) -> int:
        items, rv = self._store.list(self.kind)
        with self._lock:
            fresh = {self._obj_key(o): o for o in items}
            stale = set(self._cache) - set(fresh)
            for key in stale:
                old = self._cache.pop(key)
                self._emit(st.DELETED, old, None)
            for key, obj in fresh.items():
                old = self._cache.get(key)
                self._cache[key] = obj
                if old is None:
                    self._emit(st.ADDED, obj, None)
                elif old.meta.resource_version != obj.meta.resource_version:
                    self._emit(st.MODIFIED, obj, old)
        return rv

    def _stream(self, rv: int) -> None:
        self._watch = self._store.watch(self.kind, from_rv=rv)
        try:
            for ev in self._watch:
                if self._stop.is_set():
                    return
                with self._lock:
                    key = self._obj_key(ev.obj)
                    if ev.type == st.DELETED:
                        old = self._cache.pop(key, None)
                        self._emit(st.DELETED, ev.obj, old)
                    else:
                        old = self._cache.get(key)
                        self._cache[key] = ev.obj
                        self._emit(
                            st.ADDED if old is None else st.MODIFIED, ev.obj, old
                        )
        finally:
            self._watch = None
        # stream ended (overflow / store closed it): loop relists

    def _emit(self, typ: str, obj: Any, old: Optional[Any]) -> None:
        # Handler faults must not kill the stream or starve later handlers
        # (client-go's processorListener delivery is panic-isolated per
        # listener); the local cache was already updated, so a dead stream
        # would never re-deliver this event after relist.
        for h in self._handlers:
            try:
                h(typ, obj, old)
            except Exception:
                logger.exception(
                    "informer %s: handler %r failed on %s", self.kind, h, typ
                )


class InformerFactory:
    """SharedInformerFactory: one informer per kind, shared by consumers."""

    def __init__(self, store: st.Store):
        self.store = store
        self._informers: Dict[str, SharedInformer] = {}
        self._lock = threading.Lock()

    def informer(self, kind: str) -> SharedInformer:
        with self._lock:
            inf = self._informers.get(kind)
            if inf is None:
                inf = SharedInformer(self.store, kind)
                self._informers[kind] = inf
            return inf

    def start(self) -> None:
        with self._lock:
            for inf in self._informers.values():
                inf.start()

    def stop(self) -> None:
        with self._lock:
            for inf in self._informers.values():
                inf.stop()

    def wait_for_sync(self, timeout: Optional[float] = 10) -> bool:
        """WaitForCacheSync over STARTED informers — a registered but
        never-started informer cannot sync (tests start subsets; the
        reference's WaitForCacheSync likewise takes the informers the
        caller chose to run)."""
        with self._lock:
            infs = [i for i in self._informers.values() if i._thread]
        return all(inf.wait_for_sync(timeout) for inf in infs)
