"""Informer machinery: reflector + shared informer + listers.

The client-go cache stack (tools/cache) reduced to its load-bearing
parts:

  Reflector      list+watch against the Store, relisting on Expired /
                 stream termination (reflector.go:340 ListAndWatch, the
                 410-Gone relist path) with jittered exponential backoff
                 on consecutive expiries, through a shared RelistGate
                 bounding concurrent relists (storm containment)
  SharedInformer local thread-safe object cache + handler fan-out
                 (shared_informer.go:459 Run; handlers get add/update/
                 delete callbacks after an initial synthetic-ADDED sync,
                 DeltaFIFO's replace semantics)
  Lister         snapshot reads of the informer cache (listers)

Transport is the in-process api.store.Store — the deployment analogue of
client-go speaking to the apiserver's watch cache.  Delivery runs on one
informer thread per kind (client-go's single event goroutine per
informer); handlers must not block it.
"""

from __future__ import annotations

import logging
import random
import threading
from typing import Any, Callable, Dict, List, Optional

from ..api import store as st

logger = logging.getLogger(__name__)

Handler = Callable[[str, Any, Optional[Any]], None]
# Handler(event_type, obj, old_obj): old_obj set for MODIFIED only.


class RelistGate:
    """Shared relist limiter: when N informers expire together (a relist
    storm — the store expired their watches in one overload episode), a
    bounded semaphore caps how many hit `Store.list` concurrently; the
    rest queue on the gate instead of synchronously hammering the one
    snapshot path every consumer is already waiting on.  Combined with
    each reflector's jittered backoff, simultaneous expiries de-correlate
    instead of re-synchronizing on the next relist."""

    def __init__(self, max_concurrent: int = 2):
        self.max_concurrent = max_concurrent
        self._sem = threading.BoundedSemaphore(max_concurrent)

    def __enter__(self) -> "RelistGate":
        self._sem.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self._sem.release()


class SharedInformer:
    """One kind's local cache, kept in sync by a reflector thread."""

    # jittered exponential backoff on Expired (the 410/overflow path):
    # base doubles per consecutive expiry up to the cap; the actual wait
    # is uniform in [cap/2, cap] so simultaneous expiries spread
    _RELIST_BACKOFF_BASE = 0.05
    _RELIST_BACKOFF_MAX = 2.0

    def __init__(
        self,
        store: st.Store,
        kind: str,
        relist_gate: Optional[RelistGate] = None,
    ):
        self._store = store
        self.kind = kind
        self._lock = threading.RLock()
        self._cache: Dict[str, Any] = {}
        self._handlers: List[Handler] = []
        self._synced = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._watch: Optional[st.Watch] = None
        self._gate = relist_gate or RelistGate()
        self._expired_streak = 0  # consecutive Expired relists
        self._rng = random.Random()
        self.relists = 0          # observability (tests assert recovery)
        # rv of the most recent relist cut.  With the sharded store a
        # list() is a point-in-time-consistent cut across every shard
        # (taken under the publish lock: sub-waves are all-or-nothing in
        # it, and every item's rv is <= this value) — tests assert the
        # cut contract through this bookmark.
        self.last_relist_rv = 0

    # -- wiring ------------------------------------------------------------

    def add_handler(self, handler: Handler, replay: bool = True) -> None:
        """Register a handler; when replay (the shared-informer contract),
        it first receives synthetic ADDED events for the current cache."""
        with self._lock:
            if replay:
                for obj in self._cache.values():
                    try:
                        handler(st.ADDED, obj, None)
                    except Exception:
                        logger.exception(
                            "informer %s: handler %r failed on replay",
                            self.kind, handler,
                        )
            self._handlers.append(handler)

    def start(self) -> None:
        if self._thread:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"informer-{self.kind}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        w = self._watch
        if w:
            w.stop()
        if self._thread:
            self._thread.join(timeout=5)

    def wait_for_sync(self, timeout: Optional[float] = 10) -> bool:
        """WaitForCacheSync: true once the initial list landed."""
        return self._synced.wait(timeout)

    # -- reads (listers) ---------------------------------------------------

    def get(self, name: str, namespace: str = "default") -> Optional[Any]:
        with self._lock:
            return self._cache.get(self._key(namespace, name))

    def list(self) -> List[Any]:
        with self._lock:
            return list(self._cache.values())

    @staticmethod
    def _key(namespace: str, name: str) -> str:
        return f"{namespace}/{name}" if namespace else name

    def _obj_key(self, obj: Any) -> str:
        return self._key(obj.meta.namespace, obj.meta.name)

    # -- reflector loop ----------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                rv = self._relist()
                self._synced.set()
                self._expired_streak = 0  # a stream established == healthy
                self._stream(rv)
            except st.Expired:
                # the 410 path: watch(from_rv) too old, replay overflow,
                # or the store expired the stream (coalescing overflow).
                # Jittered exponential backoff so N informers expiring
                # together don't relist in lockstep (relist storm).
                self._stop.wait(self._expired_delay())
                continue
            except Exception:
                if self._stop.is_set():
                    return
                self._stop.wait(0.05)  # backoff then relist

    def _expired_delay(self) -> float:
        self._expired_streak = min(self._expired_streak + 1, 8)
        cap = min(
            self._RELIST_BACKOFF_BASE * (2 ** (self._expired_streak - 1)),
            self._RELIST_BACKOFF_MAX,
        )
        return self._rng.uniform(cap / 2, cap)

    def _relist(self) -> int:
        with self._gate:  # bounded concurrent relists (storm containment)
            items, rv = self._store.list(self.kind)
        self.relists += 1
        self.last_relist_rv = rv
        with self._lock:
            fresh = {self._obj_key(o): o for o in items}
            stale = set(self._cache) - set(fresh)
            for key in stale:
                old = self._cache.pop(key)
                self._emit(st.DELETED, old, None)
            for key, obj in fresh.items():
                old = self._cache.get(key)
                if old is not None and self._recreated(old, obj):
                    self._cache.pop(key)
                    self._emit(st.DELETED, old, None)
                    old = None
                self._cache[key] = obj
                if old is None:
                    self._emit(st.ADDED, obj, None)
                elif old.meta.resource_version != obj.meta.resource_version:
                    self._emit(st.MODIFIED, obj, old)
        return rv

    @staticmethod
    def _recreated(old, new) -> bool:
        """True when `new` is a DIFFERENT object under the same key — a
        delete + recreate the watch path compacted into one event (or a
        relist jumped over).  The split is re-synthesized as
        DELETED(old) + ADDED(new) so uid-sensitive consumers (the PV
        controller's claimRef.UID check, the scheduler cache's
        accounting) see the true transition."""
        old_uid = getattr(old.meta, "uid", "")
        new_uid = getattr(new.meta, "uid", "")
        return bool(old_uid) and bool(new_uid) and old_uid != new_uid

    def _stream(self, rv: int) -> None:
        self._watch = self._store.watch(self.kind, from_rv=rv)
        try:
            for ev in self._watch:
                if self._stop.is_set():
                    return
                with self._lock:
                    key = self._obj_key(ev.obj)
                    if ev.type == st.DELETED:
                        old = self._cache.pop(key, None)
                        self._emit(st.DELETED, ev.obj, old)
                    else:
                        old = self._cache.get(key)
                        if old is not None and self._recreated(old, ev.obj):
                            # delete + recreate compacted by the watch
                            # buffer: synthesize the split
                            self._cache.pop(key)
                            self._emit(st.DELETED, old, None)
                            old = None
                        self._cache[key] = ev.obj
                        self._emit(
                            st.ADDED if old is None else st.MODIFIED, ev.obj, old
                        )
        finally:
            self._watch = None
        # stream ended (consumer stop / store closed it): loop relists.
        # An EXPIRED stream raises st.Expired out of the iteration above
        # instead — _run's 410 handler adds the jittered backoff.

    def _emit(self, typ: str, obj: Any, old: Optional[Any]) -> None:
        # Handler faults must not kill the stream or starve later handlers
        # (client-go's processorListener delivery is panic-isolated per
        # listener); the local cache was already updated, so a dead stream
        # would never re-deliver this event after relist.
        for h in self._handlers:
            try:
                h(typ, obj, old)
            except Exception:
                logger.exception(
                    "informer %s: handler %r failed on %s", self.kind, h, typ
                )


class InformerFactory:
    """SharedInformerFactory: one informer per kind, shared by consumers."""

    def __init__(self, store: st.Store):
        self.store = store
        self._informers: Dict[str, SharedInformer] = {}
        self._lock = threading.Lock()
        # one gate for every informer this factory hands out: the
        # relist-storm bound is per CONSUMER PROCESS, not per kind
        self.relist_gate = RelistGate()

    def informer(self, kind: str) -> SharedInformer:
        with self._lock:
            inf = self._informers.get(kind)
            if inf is None:
                inf = SharedInformer(
                    self.store, kind, relist_gate=self.relist_gate
                )
                self._informers[kind] = inf
            return inf

    def start(self) -> None:
        with self._lock:
            for inf in self._informers.values():
                inf.start()

    def stop(self) -> None:
        with self._lock:
            for inf in self._informers.values():
                inf.stop()

    def wait_for_sync(self, timeout: Optional[float] = 10) -> bool:
        """WaitForCacheSync over STARTED informers — a registered but
        never-started informer cannot sync (tests start subsets; the
        reference's WaitForCacheSync likewise takes the informers the
        caller chose to run)."""
        with self._lock:
            infs = [i for i in self._informers.values() if i._thread]
        return all(inf.wait_for_sync(timeout) for inf in infs)
