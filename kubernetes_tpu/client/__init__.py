"""Client layer: shared informers + listers over the API store's watch
streams, and rate-limited work queues — the client-go tools/cache +
util/workqueue analogue (SURVEY.md layer 7)."""

from .informers import InformerFactory, SharedInformer
from .workqueue import WorkQueue

__all__ = ["InformerFactory", "SharedInformer", "WorkQueue"]
