"""The proto snapshot service: foreign control planes drive the TPU
solver with dense tensors.

Reference framing: SURVEY §2.6's north-star boundary — the analogue of
the CRI's proto contract (cri-api/pkg/apis/runtime/v1/api.proto) at the
scheduling seam.  Where the HTTP extender (extender/server.py) speaks
the reference's per-node JSON (extender/v1/types.go), this service
speaks kubernetes_tpu/proto/snapshot.proto: column-ordered matrices
that decode straight into the device tensor schema, so a Go or C++
scheduler core can hand off an entire batch in one round trip.

Transport: protobuf messages over TCP with 4-byte big-endian length
framing (the standard protobuf stream framing).  grpcio is not in this
image; the service keyword in the .proto keeps the contract
gRPC-generatable — a grpc server is a ~20-line wrapper over
ProtoBackend.solve when the dependency exists.  native/proto_client.cpp
is the stock-C++ proof (protoc-generated code, no Python anywhere).

Wire contract notes:
  * request `requested` rows describe CURRENT node usage; the backend
    accounts them as one synthetic bound pod per non-empty row, so the
    solve sees the same free vectors the caller's cache holds.
  * group_ids drive gang all-or-nothing through the solver's native
    gang machinery.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
import time
from typing import Optional

import numpy as np

from ..api import types as api
from ..models.batch_scheduler import TPUBatchScheduler
from ..proto import snapshot_pb2 as pb

MAX_MESSAGE = 256 * 1024 * 1024


def _read_exact(rfile, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = rfile.read(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf += chunk
    return buf


def read_frame(rfile) -> bytes:
    (n,) = struct.unpack(">I", _read_exact(rfile, 4))
    if n > MAX_MESSAGE:
        raise ValueError(f"frame of {n} bytes exceeds {MAX_MESSAGE}")
    return _read_exact(rfile, n)


def write_frame(wfile, payload: bytes) -> None:
    wfile.write(struct.pack(">I", len(payload)) + payload)
    wfile.flush()


def _matrix(m: pb.DenseMatrix) -> np.ndarray:
    a = np.asarray(m.data, dtype=np.float32)
    if m.rows * m.cols != a.size:
        raise ValueError(
            f"matrix {m.rows}x{m.cols} carries {a.size} values"
        )
    return a.reshape(m.rows, m.cols)


class ProtoBackend:
    """Decodes SolveRequests into the solver's object model and runs
    one stateless batched solve per request."""

    def solve(self, req: pb.SolveRequest) -> pb.SolveResponse:
        t0 = time.perf_counter()
        vocab = list(req.cluster.resources.names)
        alloc = _matrix(req.cluster.allocatable)
        node_names = list(req.cluster.node_names)
        if alloc.shape[0] != len(node_names):
            raise ValueError("allocatable rows != node_names")
        used = (
            _matrix(req.cluster.requested)
            if req.cluster.requested.rows
            else None
        )
        nodes, bound = [], []
        for i, name in enumerate(node_names):
            nodes.append(
                api.Node(
                    meta=api.ObjectMeta(
                        name=name,
                        namespace="",
                        labels={api.LABEL_HOSTNAME: name},
                    ),
                    status=api.NodeStatus(
                        allocatable={
                            vocab[j]: int(alloc[i, j])
                            for j in range(len(vocab))
                            if alloc[i, j]
                        }
                    ),
                )
            )
            if used is not None and used[i].any():
                # current usage rides one synthetic bound pod per node —
                # the public accounting path, so free vectors match the
                # caller's cache exactly
                p = api.Pod(
                    meta=api.ObjectMeta(name=f"__usage-{name}"),
                    spec=api.PodSpec(
                        node_name=name,
                        containers=[
                            api.Container(
                                requests={
                                    vocab[j]: int(used[i, j])
                                    for j in range(len(vocab))
                                    if used[i, j]
                                }
                            )
                        ],
                    ),
                )
                bound.append(p)
        reqs = _matrix(req.pods.requests)
        pods = []
        for i, name in enumerate(req.pods.pod_names):
            spec = api.PodSpec(
                containers=[
                    api.Container(
                        requests={
                            vocab[j]: int(reqs[i, j])
                            for j in range(len(vocab))
                            if reqs[i, j]
                        }
                    )
                ]
            )
            if i < len(req.pods.priorities):
                spec.priority = req.pods.priorities[i]
            if i < len(req.pods.group_ids) and req.pods.group_ids[i]:
                spec.scheduling_group = req.pods.group_ids[i]
            pods.append(
                api.Pod(meta=api.ObjectMeta(name=name), spec=spec)
            )
        solver = TPUBatchScheduler()
        names = solver.schedule(nodes, pods, bound=bound)
        result = solver.last_result
        reasons = (
            [int(r) for r in np.asarray(result.reasons)[: len(pods)]]
            if result is not None and result.reasons is not None
            else [-1] * len(pods)
        )
        node_index = {n: i for i, n in enumerate(node_names)}
        resp = pb.SolveResponse(solve_seconds=time.perf_counter() - t0)
        for pod, node in zip(pods, names):
            resp.assignments.add(
                pod_name=pod.meta.name,
                node_name=node or "",
                node_index=node_index.get(node, -1) if node else -1,
            )
        resp.reasons.extend(reasons)
        return resp


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        while True:
            try:
                payload = read_frame(self.rfile)
            except (ConnectionError, struct.error):
                return
            req = pb.SolveRequest()
            req.ParseFromString(payload)
            resp = self.server.backend.solve(req)  # type: ignore[attr-defined]
            write_frame(self.wfile, resp.SerializeToString())


class ProtoSchedulerServer:
    """TCP server speaking length-framed snapshot.proto messages."""

    def __init__(
        self,
        backend: Optional[ProtoBackend] = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.server = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=True
        )
        self.server.daemon_threads = True
        self.server.backend = backend or ProtoBackend()
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.server.server_address[1]

    def start(self) -> "ProtoSchedulerServer":
        self._thread = threading.Thread(
            target=self.server.serve_forever, name="proto-scheduler",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
        if self._thread:
            self._thread.join(timeout=5)


def solve_over_socket(host: str, port: int, req: pb.SolveRequest) -> pb.SolveResponse:
    """Client helper: one framed round trip (what a Go/C++ client does
    with its own generated code)."""
    with socket.create_connection((host, port)) as s:
        f = s.makefile("rwb")
        write_frame(f, req.SerializeToString())
        resp = pb.SolveResponse()
        resp.ParseFromString(read_frame(f))
        return resp
