"""The scheduler-extender HTTP endpoint — the north-star integration
contract: a STOCK kube-scheduler configured with this extender delegates
Filter/Prioritize (and optionally Bind) to the TPU solver, no scheduler
rebuild required (pkg/scheduler/extender.go:86-455; wire types mirrored
in .types).

Verbs (HTTP POST, JSON bodies; paths are configured on the kube side via
KubeSchedulerConfiguration extenders[].{filterVerb,prioritizeVerb,...}):

  /filter      ExtenderArgs -> ExtenderFilterResult
  /prioritize  ExtenderArgs -> HostPriorityList
  /bind        ExtenderBindingArgs -> ExtenderBindingResult
  /preemption  ExtenderPreemptionArgs -> ExtenderPreemptionResult
  /healthz, /readyz  GET liveness/readiness (app/server.go:169-199)

nodeCacheCapable=true is the intended mode: the request ships node NAMES
only and the extender evaluates against its own incremental ClusterState
(fed by add_node/remove_node, or by pointing sync_store() at the
in-process API store).  Non-cache mode (full Node objects in the
request) is also accepted: nodes are upserted into the state before
evaluating, so a bare extender works without any feed.

Example kube-side config (docs/extender.md has the full walkthrough):

    apiVersion: kubescheduler.config.k8s.io/v1
    kind: KubeSchedulerConfiguration
    extenders:
      - urlPrefix: "http://tpu-extender:12346"
        filterVerb: "filter"
        prioritizeVerb: "prioritize"
        weight: 5
        nodeCacheCapable: true
        enableHTTPS: false
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api import store as st
from ..api import types as api
from ..models.batch_scheduler import TPUBatchScheduler
from ..ops import assign as assign_ops
from . import types as wire


class ExtenderBackend:
    """The verb implementations, HTTP-free (tests drive this directly)."""

    def __init__(
        self,
        tpu: Optional[TPUBatchScheduler] = None,
        store: Optional[st.Store] = None,
        lock: Optional[threading.RLock] = None,
    ):
        self.tpu = tpu or TPUBatchScheduler()
        self.store = store
        self.lock = lock or threading.RLock()

    # -- node inventory ----------------------------------------------------

    def add_node(self, node: api.Node) -> None:
        with self.lock:
            self.tpu.state.add_node(node)

    def remove_node(self, name: str) -> None:
        with self.lock:
            self.tpu.state.remove_node(name)

    def sync_store(self, store: st.Store) -> None:
        """Feed the state from an API store's current nodes + bound pods
        (one-shot; informer-driven continuous sync is the host
        scheduler's job — the extender is typically deployed beside a
        kube cluster and fed by its own watch)."""
        self.store = store
        with self.lock:
            nodes, _ = store.list("Node")
            for n in nodes:
                self.tpu.state.add_node(n)
            pods, _ = store.list("Pod")
            for p in pods:
                if p.spec.node_name and not self.tpu.state.has_pod(p):
                    self.tpu.state.add_pod(p)

    # -- verbs -------------------------------------------------------------

    def _evaluate(
        self, pod: api.Pod
    ) -> Tuple[Dict[str, bool], Dict[str, float]]:
        """(feasible-by-node-name, score-by-node-name) over live state."""
        with self.lock:
            snap, meta = self.tpu.builder.build_from_state(
                self.tpu.state, [pod]
            )
            feas, scores = assign_ops.evaluate_single(snap)
            feas = np.asarray(feas)
            scores = np.asarray(scores)
            names = meta.node_names
        out_f: Dict[str, bool] = {}
        out_s: Dict[str, float] = {}
        for row, name in enumerate(names):
            if name is None:
                continue
            out_f[name] = bool(feas[row])
            out_s[name] = float(scores[row]) if feas[row] else 0.0
        return out_f, out_s

    def _candidates(self, args: wire.ExtenderArgs) -> List[str]:
        """Candidate node names; non-cache mode also upserts the shipped
        Node objects so both verbs work without a pre-fed inventory."""
        if args.nodes is not None:
            with self.lock:
                for n in args.nodes:
                    self.tpu.state.add_node(n)
            return [n.meta.name for n in args.nodes]
        return args.node_names or []

    def filter(self, args: wire.ExtenderArgs) -> dict:
        try:
            candidates = self._candidates(args)
            feas, _ = self._evaluate(args.pod)
            passed = [n for n in candidates if feas.get(n)]
            failed = {
                n: "node infeasible for pod (TPU batch filter)"
                for n in candidates
                if not feas.get(n)
            }
            if args.raw_nodes is not None:
                # non-cache callers read Nodes.items, not NodeNames
                passed_set = set(passed)
                items = [
                    d for d in args.raw_nodes
                    if (d.get("metadata") or {}).get("name") in passed_set
                ]
                return wire.filter_result(
                    node_names=passed, nodes=items, failed=failed
                )
            return wire.filter_result(node_names=passed, failed=failed)
        except Exception as e:  # wire errors, never tracebacks
            return wire.filter_result(node_names=[], error=str(e))

    def prioritize(self, args: wire.ExtenderArgs) -> List[dict]:
        try:
            candidates = self._candidates(args)
            _, scores = self._evaluate(args.pod)
        except Exception:
            # HostPriorityList has no Error field (types.go:125); a zeroed
            # list keeps the scheduling cycle alive (the scheduler treats
            # extender prioritize errors as fatal for the pod)
            return wire.host_priority_list({})
        vals = [scores.get(n, 0.0) for n in candidates]
        hi = max(vals) if vals else 0.0
        out: Dict[str, int] = {}
        for n, v in zip(candidates, vals):
            # scale into [0, MaxExtenderPriority]; the scheduler rescales
            # by weight * MaxNodeScore / MaxExtenderPriority
            # (schedule_one.go:827)
            out[n] = (
                int(round(v * wire.MAX_EXTENDER_PRIORITY / hi)) if hi > 0 else 0
            )
        return wire.host_priority_list(out)

    def bind(self, body: dict) -> dict:
        if self.store is None:
            return wire.binding_result("extender has no API store to bind through")
        name = body.get("PodName", "")
        namespace = body.get("PodNamespace", "default")
        node = body.get("Node", "")
        try:
            pod = self.store.get("Pod", name, namespace)
            pod.spec.node_name = node
            pod.status.phase = "Running"
            self.store.update(pod)
            # account the placement in the extender's own state so later
            # filters see the consumed capacity (sync_store is one-shot)
            with self.lock:
                if not self.tpu.state.has_pod(pod):
                    self.tpu.state.add_pod(pod, node)
            return wire.binding_result()
        except Exception as e:
            return wire.binding_result(str(e))

    def preemption(self, body: dict) -> dict:
        """ProcessPreemption: the scheduler proposes victims; an extender
        may veto or shrink the sets.  We accept the proposal unchanged
        (the TPU-side dry-run verification lives in the in-process
        scheduler's own preemption path)."""
        victims = body.get("NodeNameToMetaVictims") or {}
        return {"NodeNameToMetaVictims": victims}


class _Handler(BaseHTTPRequestHandler):
    backend: ExtenderBackend  # set by serve()

    def log_message(self, fmt, *args):  # quiet
        pass

    def _reply(self, obj, code=200) -> None:
        data = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self) -> None:
        if self.path in ("/healthz", "/readyz", "/livez"):
            self._reply({"ok": True})
        else:
            self._reply({"error": f"unknown path {self.path}"}, 404)

    def do_POST(self) -> None:
        length = int(self.headers.get("Content-Length", 0))
        try:
            body = json.loads(self.rfile.read(length) or b"{}")
        except json.JSONDecodeError as e:
            self._reply({"Error": f"bad JSON: {e}"}, 400)
            return
        be = self.backend
        if self.path == "/filter":
            self._reply(be.filter(wire.ExtenderArgs.from_dict(body)))
        elif self.path == "/prioritize":
            self._reply(be.prioritize(wire.ExtenderArgs.from_dict(body)))
        elif self.path == "/bind":
            self._reply(be.bind(body))
        elif self.path == "/preemption":
            self._reply(be.preemption(body))
        else:
            self._reply({"Error": f"unknown verb {self.path}"}, 404)


class ExtenderServer:
    """Threaded HTTP server around an ExtenderBackend."""

    def __init__(self, backend: Optional[ExtenderBackend] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.backend = backend or ExtenderBackend()
        handler = type("BoundHandler", (_Handler,), {"backend": self.backend})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> "ExtenderServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="extender", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
