"""Scheduler-extender endpoint: the out-of-tree integration contract
(kube-scheduler extender v1 wire protocol backed by the TPU solver)."""

from .server import ExtenderBackend, ExtenderServer
from .types import ExtenderArgs, MAX_EXTENDER_PRIORITY

__all__ = [
    "ExtenderArgs",
    "ExtenderBackend",
    "ExtenderServer",
    "MAX_EXTENDER_PRIORITY",
]
