"""kube-scheduler extender v1 wire types.

JSON shapes match staging/src/k8s.io/kube-scheduler/extender/v1/types.go:73-132
byte-for-byte at the key level: the Go structs carry no json tags, so
encoding/json uses the exported field names verbatim ("Pod", "NodeNames",
"FailedNodes", "Error", "Host", "Score", ...).  A stock kube-scheduler
configured with this extender POSTs exactly these documents
(pkg/scheduler/extender.go:86-455, send() at :397).

Pods arrive as v1.Pod JSON and are decoded through api.kubeyaml; in
nodeCacheCapable mode (extender/v1/types.go:79-81) only node NAMES cross
the wire and the TPU side resolves them against its own cluster state —
the design BASELINE.json's north star names explicitly.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..api import kubeyaml
from ..api import types as api


class ExtenderArgs:
    """extender/v1/types.go:73 — filter/prioritize request."""

    def __init__(
        self,
        pod: api.Pod,
        node_names: Optional[List[str]] = None,
        nodes: Optional[List[api.Node]] = None,
        raw_nodes: Optional[List[Dict[str, Any]]] = None,
    ):
        self.pod = pod
        self.node_names = node_names
        self.nodes = nodes
        # original v1.Node JSON items (non-cache mode): the RESPONSE must
        # echo passing nodes as full objects — HTTPExtender.Filter reads
        # result.Nodes.Items when nodeCacheCapable is off (extender.go)
        self.raw_nodes = raw_nodes

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ExtenderArgs":
        pod = kubeyaml.pod_from_dict(d.get("Pod") or {})
        node_names = d.get("NodeNames")
        nodes = raw = None
        if d.get("Nodes") is not None:
            raw = list(d["Nodes"].get("items") or [])
            nodes = [kubeyaml.node_from_dict(item) for item in raw]
        return cls(pod, node_names, nodes, raw)


def filter_result(
    node_names: Optional[List[str]] = None,
    nodes: Optional[List[Dict[str, Any]]] = None,
    failed: Optional[Dict[str, str]] = None,
    failed_unresolvable: Optional[Dict[str, str]] = None,
    error: str = "",
) -> Dict[str, Any]:
    """ExtenderFilterResult (types.go:88).  nodeCacheCapable callers read
    NodeNames; non-cache callers read Nodes.items — populate whichever
    matches the request's shape."""
    return {
        "Nodes": {"items": nodes} if nodes is not None else None,
        "NodeNames": node_names,
        "FailedNodes": failed or {},
        "FailedAndUnresolvableNodes": failed_unresolvable or {},
        "Error": error,
    }


def host_priority_list(scores: Dict[str, int]) -> List[Dict[str, Any]]:
    """HostPriorityList (types.go:125-132)."""
    return [{"Host": h, "Score": int(s)} for h, s in scores.items()]


def binding_result(error: str = "") -> Dict[str, Any]:
    return {"Error": error}


# MaxExtenderPriority — the scheduler scales extender scores by
# weight * MaxNodeScore / MaxExtenderPriority (schedule_one.go:827)
MAX_EXTENDER_PRIORITY = 10
