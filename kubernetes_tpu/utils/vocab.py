"""Append-only vocabularies and bitset packing for tensorizing label sets.

The TPU solve cannot operate on strings, so every string-shaped piece of
cluster state (label key=value pairs, taint identities, host ports, node
names, topology values) is interned into a dense integer vocabulary on the
host and shipped to the device as packed uint32 bitsets.  Interning is
EXACT — unlike hashing there are no collisions, so filter semantics match
the reference bit-for-bit.

Set-membership machine model on device:
    node_bits : uint32[N, W]       (W = ceil(capacity/32) words)
    id i is present on node n  <=>  (node_bits[n, i>>5] >> (i & 31)) & 1

Vocabularies are append-only so node-side bitsets stay valid across
incremental snapshot updates (the device-side analogue of the reference's
generation-based incremental UpdateSnapshot,
pkg/scheduler/internal/cache/cache.go:185-260).
"""

from __future__ import annotations

from typing import Dict, Hashable, Iterable, List, Sequence, Tuple

import numpy as np


class Vocab:
    """Interns hashable items to dense ids [0, len)."""

    def __init__(self) -> None:
        self._ids: Dict[Hashable, int] = {}
        self._items: List[Hashable] = []

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, item: Hashable) -> bool:
        return item in self._ids

    def intern(self, item: Hashable) -> int:
        i = self._ids.get(item)
        if i is None:
            i = len(self._items)
            self._ids[item] = i
            self._items.append(item)
        return i

    def get(self, item: Hashable, default: int = -1) -> int:
        return self._ids.get(item, default)

    def intern_many(self, items: Sequence[Hashable]) -> np.ndarray:
        """Bulk intern: one pass, one returned id vector (int32).  Ids
        are assigned in item order, identical to looping intern() —
        this is the columnar encode's batch interning primitive, hoisting
        the per-call overhead out of hot per-object loops."""
        get = self._ids.get
        out = np.empty(len(items), dtype=np.int32)
        for j, item in enumerate(items):
            i = get(item)
            if i is None:
                i = self.intern(item)
            out[j] = i
        return out

    def get_many(self, items: Sequence[Hashable], default: int = -1) -> np.ndarray:
        """Bulk lookup without growth: int32 id vector, `default` where
        absent."""
        get = self._ids.get
        return np.fromiter(
            (get(item, default) for item in items),
            dtype=np.int32,
            count=len(items),
        )

    def alias(self, item: Hashable, ident: int) -> None:
        """Map an additional name onto an existing id (image tags/digests
        aliasing one image).  Does not grow the id space."""
        self._ids[item] = ident

    def item(self, i: int) -> Hashable:
        return self._items[i]

    def items(self) -> Sequence[Hashable]:
        return self._items


class PairVocab(Vocab):
    """Vocabulary of (key, value) pairs with a key -> ids reverse index,
    used to expand `Exists key` expressions into the exact id set present
    in the cluster."""

    def __init__(self) -> None:
        super().__init__()
        self._by_key: Dict[str, List[int]] = {}

    def intern(self, item: Tuple[str, str]) -> int:
        known = item in self._ids
        i = super().intern(item)
        if not known:
            self._by_key.setdefault(item[0], []).append(i)
        return i

    def ids_for_key(self, key: str) -> List[int]:
        return list(self._by_key.get(key, ()))


def words_for(capacity: int) -> int:
    return max(1, (capacity + 31) // 32)


def pack_bits(ids: Iterable[int], num_words: int) -> np.ndarray:
    """Pack a set of ids into a uint32[num_words] bitset."""
    out = np.zeros(num_words, dtype=np.uint32)
    for i in ids:
        if i < 0:
            continue
        w = i >> 5
        if w >= num_words:
            raise OverflowError(
                f"id {i} exceeds bitset capacity {num_words * 32}; "
                "raise the corresponding SnapshotLimits field"
            )
        out[w] |= np.uint32(1 << (i & 31))
    return out


def set_bit(bits: np.ndarray, i: int) -> None:
    w = i >> 5
    if w >= bits.shape[-1] or i < 0:
        raise OverflowError(
            f"id {i} exceeds bitset capacity {bits.shape[-1] * 32}; "
            "raise the corresponding SnapshotLimits capacity"
        )
    bits[w] |= np.uint32(1 << (i & 31))


def pad_ids(ids: Sequence[int], k: int, fill: int = -1) -> np.ndarray:
    """Fixed-width id list (int32[k]), -1 padded."""
    if len(ids) > k:
        raise OverflowError(f"{len(ids)} ids exceed slot width {k}")
    out = np.full(k, fill, dtype=np.int32)
    out[: len(ids)] = np.asarray(list(ids), dtype=np.int32)
    return out


def round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def pad_dim(n: int, minimum: int = 8) -> int:
    """Round a dimension up to a compile-friendly bucket (powers of two,
    floored at `minimum`) so repeated snapshots reuse the XLA executable."""
    size = max(n, minimum)
    bucket = 1 << (size - 1).bit_length()
    return bucket


def is_pad_bucket(n: int, minimum: int = 1) -> bool:
    """True when n is a value pad_dim can produce (a power of two no
    smaller than the floor) — the recompile-discipline pass's landing
    check for encode-determined axes (analysis/shapes.py)."""
    minimum = pad_dim(minimum, 1) if minimum > 1 else 1
    return n >= minimum and (n & (n - 1)) == 0


def is_constraint_bucket(n: int) -> bool:
    """True when n is a value pad_constraint_dim can produce: 1 (no
    rows) or a power of two floored at 32."""
    return n == 1 or (n >= 32 and is_pad_bucket(n))


def pad_constraint_dim(n: int) -> int:
    """Constraint-table row dims (selector/spread/term/preferred rows).
    Zero rows stay at dim 1 — the feature flags gate the whole family
    off and the [1, N] zero table costs one cached fill.  NONZERO rows
    floor at 32: straggler batches (retries, late arrivals) carry
    arbitrary subsets of the main batch's constraint classes, and
    per-power-of-two row dims would compile a fresh executable for
    nearly every straggler composition — the dominant in-window compile
    source for constraint workloads."""
    if n == 0:
        return 1
    return pad_dim(n, 32)
