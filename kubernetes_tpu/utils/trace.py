"""Poor-man's op tracing: timed steps logged when a threshold is blown.

Reference: utiltrace.New("Scheduling", ...) with LogIfLong(100ms) steps
inside schedulePod (schedule_one.go:391-431) — the lightweight always-on
layer under the OTel integration.  A Trace collects named steps; if the
total exceeds the threshold at the end of the `with` block, every step
is logged with its share, so slow cycles self-describe in logs without a
profiler attached.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("kubernetes_tpu.trace")

# Over-threshold traces, recorded alongside the log line so harnesses
# (bench.py BENCH_STRICT) can FAIL on slow cycles instead of merely
# warning into a log nobody greps.  Bounded; drain_overruns() empties it.
_OVERRUNS: List[Dict] = []
_OVERRUNS_LOCK = threading.Lock()
_OVERRUNS_CAP = 256


def drain_overruns() -> List[Dict]:
    """Return and clear the recorded over-threshold traces.  Each entry:
    {name, total_s, threshold_s, fields, steps: [(what, seconds)]}."""
    with _OVERRUNS_LOCK:
        out = list(_OVERRUNS)
        _OVERRUNS.clear()
    return out


class Trace:
    def __init__(self, name: str, threshold: float = 0.1, clock=time.monotonic,
                 **fields):
        self.name = name
        self.threshold = threshold
        self._clock = clock
        self.fields = fields
        self._t0 = clock()
        self._last = self._t0
        self._logged = False
        self._log_lock = threading.Lock()
        self.steps: List[Tuple[str, float]] = []

    def step(self, what: str) -> None:
        now = self._clock()
        self.steps.append((what, now - self._last))
        self._last = now

    @property
    def total(self) -> float:
        return self._clock() - self._t0

    def __enter__(self) -> "Trace":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.log_if_long()

    def log_if_long(self, threshold: Optional[float] = None) -> None:
        limit = self.threshold if threshold is None else threshold
        total = self.total
        # Exactly once per trace, even when a caller's explicit exit-path
        # call races or stacks with the with-block exit (the r05 bench
        # tail showed every over-threshold schedule_batch trace twice —
        # the explicit call at the end of the group loop plus __exit__,
        # each formatting its own slightly-later total).  The flag is
        # checked-and-set under a lock so a trace finalized from another
        # thread (deferred-cycle finalize) can't double-emit either.
        if total < limit:
            return
        with self._log_lock:
            if self._logged:
                return
            self._logged = True
        tags = ",".join(f"{k}={v}" for k, v in self.fields.items())
        parts = "; ".join(f"{w}: {dt * 1e3:.1f}ms" for w, dt in self.steps)
        logger.warning(
            "trace %s (%s) took %.1fms (threshold %.0fms): %s",
            self.name, tags, total * 1e3, limit * 1e3, parts,
        )
        with _OVERRUNS_LOCK:
            if len(_OVERRUNS) < _OVERRUNS_CAP:
                _OVERRUNS.append(
                    {
                        "name": self.name,
                        "total_s": round(total, 4),
                        "threshold_s": limit,
                        "fields": dict(self.fields),
                        "steps": [
                            (w, round(dt, 4)) for w, dt in self.steps
                        ],
                    }
                )
