"""Poor-man's op tracing: timed steps logged when a threshold is blown.

Reference: utiltrace.New("Scheduling", ...) with LogIfLong(100ms) steps
inside schedulePod (schedule_one.go:391-431) — the lightweight always-on
layer under the OTel integration.  A Trace collects named steps; if the
total exceeds the threshold at the end of the `with` block, every step
is logged with its share, so slow cycles self-describe in logs without a
profiler attached.
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Tuple

logger = logging.getLogger("kubernetes_tpu.trace")


class Trace:
    def __init__(self, name: str, threshold: float = 0.1, clock=time.monotonic,
                 **fields):
        self.name = name
        self.threshold = threshold
        self._clock = clock
        self.fields = fields
        self._t0 = clock()
        self._last = self._t0
        self.steps: List[Tuple[str, float]] = []

    def step(self, what: str) -> None:
        now = self._clock()
        self.steps.append((what, now - self._last))
        self._last = now

    @property
    def total(self) -> float:
        return self._clock() - self._t0

    def __enter__(self) -> "Trace":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.log_if_long()

    def log_if_long(self, threshold: Optional[float] = None) -> None:
        limit = self.threshold if threshold is None else threshold
        total = self.total
        if total < limit:
            return
        tags = ",".join(f"{k}={v}" for k, v in self.fields.items())
        parts = "; ".join(f"{w}: {dt * 1e3:.1f}ms" for w, dt in self.steps)
        logger.warning(
            "trace %s (%s) took %.1fms (threshold %.0fms): %s",
            self.name, tags, total * 1e3, limit * 1e3, parts,
        )
