"""Feature gates — staged feature lifecycle with override validation.

Reference: component-base/featuregate/feature_gate.go +
pkg/features/kube_features.go: a known-features map with per-feature
default + maturity stage, overridden by `--feature-gates=Foo=true` /
componentconfig maps, consulted at plugin-registry/router build time
(plugins/registry.go:58-70).  GA-locked features reject overrides the
way LockToDefault does.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Mapping, Optional

ALPHA = "ALPHA"
BETA = "BETA"
GA = "GA"


@dataclass(frozen=True)
class FeatureSpec:
    default: bool
    stage: str = BETA
    lock_to_default: bool = False


# The framework's gateable behaviors (the kube_features.go analogue).
DEFAULT_FEATURES: Dict[str, FeatureSpec] = {
    # route large/gang batches to the joint auction solve instead of the
    # greedy scan (models/batch_scheduler._route)
    "AuctionSolver": FeatureSpec(True, BETA),
    # device-resident cluster mirror with delta sync (models/mirror.py)
    "DeviceClusterMirror": FeatureSpec(True, BETA),
    # incremental O(changes) solving: device-resident Filter/Score
    # partials warm-starting every greedy/wavefront solve, scatter-
    # refreshed from the mirror's dirty rows (models/partials.py).
    # Requires DeviceClusterMirror — disabled along with it.
    "IncrementalSolve": FeatureSpec(True, BETA),
    # node-axis-sharded multichip solve when the config names a mesh
    # (SchedulerConfiguration.mesh_devices; parallel/sharded.py) — off
    # pins every profile to the single chip regardless of meshDevices
    "ShardedSolve": FeatureSpec(True, BETA),
    # PV/PVC topology + attach limits in scheduling
    # (scheduler/volumebinding.py)
    "VolumeBinding": FeatureSpec(True, BETA),
    # PodDisruptionBudget-aware victim ranking (scheduler/preemption.py)
    "PDBAwarePreemption": FeatureSpec(True, BETA),
    # ResourceClaim/DeviceClass scheduling (scheduler/deviceclaims.py)
    "DynamicResourceAllocation": FeatureSpec(True, BETA),
    # gang staging in the queue + all-or-nothing post-pass; GA and
    # locked — the north-star workload depends on it
    "GangScheduling": FeatureSpec(True, GA, lock_to_default=True),
}


class FeatureGate:
    def __init__(
        self,
        known: Optional[Mapping[str, FeatureSpec]] = None,
        overrides: Optional[Mapping[str, bool]] = None,
    ):
        self._known = dict(known if known is not None else DEFAULT_FEATURES)
        self._overrides: Dict[str, bool] = {}
        if overrides:
            self.set_from_map(overrides)

    def set_from_map(self, overrides: Mapping[str, bool]) -> "FeatureGate":
        """Apply overrides, validating names and GA locks (SetFromMap)."""
        for name, value in overrides.items():
            spec = self._known.get(name)
            if spec is None:
                raise ValueError(
                    f"unknown feature gate {name!r}; known: "
                    f"{sorted(self._known)}"
                )
            if spec.lock_to_default and value != spec.default:
                raise ValueError(
                    f"feature gate {name} is {spec.stage} and locked to "
                    f"{spec.default}"
                )
            self._overrides[name] = bool(value)
        return self

    @classmethod
    def from_flag(cls, flag: str) -> "FeatureGate":
        """Parse `Foo=true,Bar=false` (the --feature-gates flag shape)."""
        overrides = {}
        for part in flag.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, raw = part.partition("=")
            if raw.lower() not in ("true", "false"):
                raise ValueError(
                    f"feature gate {part!r}: value must be true|false"
                )
            overrides[name.strip()] = raw.lower() == "true"
        return cls(overrides=overrides)

    def enabled(self, name: str) -> bool:
        if name in self._overrides:
            return self._overrides[name]
        spec = self._known.get(name)
        if spec is None:
            raise ValueError(f"unknown feature gate {name!r}")
        return spec.default

    def as_map(self) -> Dict[str, bool]:
        return {name: self.enabled(name) for name in self._known}
