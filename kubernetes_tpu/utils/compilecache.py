"""Persistent XLA compilation cache.

The scheduler's solvers are jitted per shape bucket; a cold process pays
10-40 s of XLA compile per bucket, which is the dominant wall-clock cost
of small workloads (a 500-pod SchedulingBasic run spends ~95% of its
wall time compiling).  The reference has no analogue — Go compiles ahead
of time — so to compete on wall clock the executables must survive the
process: JAX's persistent compilation cache serializes every compiled
program to disk keyed by (HLO, compile options, platform version), and
later processes deserialize in milliseconds instead of recompiling.

Enabled on import of kubernetes_tpu (kubernetes_tpu/__init__.py) unless
KUBERNETES_TPU_NO_COMPILE_CACHE is set.  The cache dir defaults to
~/.cache/kubernetes_tpu/jax and is overridable via
KUBERNETES_TPU_JAX_CACHE_DIR.

Reference framing: this plays the role the reference's ahead-of-time
compilation plays — scheduling code is ready the moment the binary
starts (cmd/kube-scheduler is a compiled Go binary; our "binary" is the
jax cache + the Python package).
"""

from __future__ import annotations

import logging
import os

_log = logging.getLogger(__name__)
_enabled_dir: str | None = None


def enable(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at `cache_dir` (created
    if needed).  Idempotent; returns the active dir or None if disabled
    or unsupported.  Every compile is cached (min-time/min-size gates
    zeroed): even 100 ms executables are worth never recompiling, and
    the scheduler's shape-bucket family is small enough that cache size
    is not a concern."""
    global _enabled_dir
    if os.environ.get("KUBERNETES_TPU_NO_COMPILE_CACHE"):
        return None
    if _enabled_dir is not None:
        return _enabled_dir
    cache_dir = (
        cache_dir
        or os.environ.get("KUBERNETES_TPU_JAX_CACHE_DIR")
        or os.path.join(
            os.path.expanduser("~"), ".cache", "kubernetes_tpu", "jax"
        )
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # also persist XLA-internal (autotune etc.) caches where the
        # backend supports it
        jax.config.update("jax_persistent_cache_enable_xla_caches", "all")
    except Exception:  # pragma: no cover - unsupported backend/readonly fs
        _log.exception("persistent compilation cache unavailable; continuing")
        return None
    _enabled_dir = cache_dir
    return cache_dir
