"""Cross-cutting utilities (vocab encoding, clocks, backoff)."""
